"""Benchmark: batched decode throughput through the serving engine.

Emits JSON lines on stdout; the LAST line is the authoritative record:
{"metric", "value", "unit", "vs_baseline", ...extras}.

Headline metric: aggregate tokens/s of continuous-batching decode (batch=8)
on a 1B-class Llama-shape model (TinyLlama-1.1B dims) with the paged KV
cache and the **Pallas paged-attention kernel** — the engine's steady-state
serving path on TPU. The dense gather backend is timed too and reported as
``dense_tok_s`` so the kernel's delta is visible.

Baseline: the only decode-rate number recorded anywhere in the reference,
Ollama serving `mistral` at ~93 tok/s **single-stream** (BASELINE.md,
reference notebooks/aiohttp_tracing.ipynb cell e01c6727 output).
``vs_baseline`` compares like-for-like per-stream rate against it;
the aggregate ratio is reported separately as ``vs_baseline_aggregate``.

Resilience (round-3 lesson — BENCH_r03.json was rc=124 with ZERO output
after the TPU tunnel wedged): the parent process never imports jax, so
jax device init cannot hang it. Every jax-touching step runs in a child
subprocess in its own process group (killpg on timeout — a timeout-killed
direct child must not leave orphaned runtime helpers holding the TPU, the
very thing that wedged the round-3 tunnel) with stdout to a temp file (a
pipe could block the parent on orphan EOF). Steps:

  1. ``--probe`` child (120 s): init jax, report platform/device_kind.
     If the probe hangs twice, the parent retries it with the axon
     sitecustomize bypassed (``PYTHONPATH= JAX_PLATFORMS=cpu``) and runs
     the lanes on CPU at test scale, marked ``degraded``.
  2. One ``--lane backend:quant`` child per measurement lane
     (pallas/bf16 first — the headline — then pallas/int8, pallas/int4,
     then dense/bf16), each under a ~4.5-minute deadline. After EVERY lane a
     full snapshot record is printed+flushed, so even a driver-level kill
     mid-run leaves a parseable line with the lanes measured so far.
  3. A lane failure on TPU triggers a 60 s re-probe: tunnel gone →
     remaining lanes are skipped; tunnel fine → the lane is retried once
     (transient dial errors shouldn't cost the round its headline lane).
  4. A hard overall budget (TOTAL_BUDGET_S): no lane launches unless it
     can finish inside it, so total wall time is provably bounded at
     ~budget + one lane timeout ≈ 17 min — typical healthy-TPU runs
     finish in ~6, tunnel-dead-from-the-start runs in ~8.

If nothing can initialize at all the script still prints
``{"metric": ..., "value": null, "skipped": "tpu-unavailable"}`` and
exits 0 — a missing artifact is the one unacceptable outcome. Residual
risk this file cannot remove: in the deepest wedge state the axon
sitecustomize blocks every python interpreter at start, parent included,
before any line here runs (round-3 memory); killing whole process groups
on timeout is what keeps *this* script from creating that state.

Extras: ``mfu`` and ``hbm_util`` situate the number against chip peaks
(v5e: 394 bf16 TFLOP/s, 819 GB/s HBM) — decode at small batch is HBM-bound,
so ``hbm_util`` is the honest utilization figure. On non-TPU platforms the
model drops to test scale so the script stays fast; ratios are only
meaningful on TPU.
"""

from __future__ import annotations

import gc
import json
import os
import subprocess
import sys
import time

BASELINE_TOK_S = 93.0  # BASELINE.md: reference-side Ollama single-stream rate
# Decode slots. The default stays 8 so BENCH_r{N}.json compares across
# rounds; BENCH_BATCH=32 is the chip-sized lane (engine/autosize.py).
BATCH = int(os.environ.get("BENCH_BATCH", "8"))
# Metric key encodes model + batch (+ non-default fused-K) so a
# BENCH_BATCH/BENCH_MODEL/BENCH_KSTEPS lane can never be diffed against
# default-lane history by accident; the default spelling stays exactly
# "decode_tok_s_llama1b_bs8_pallas".
_KSTEPS = os.environ.get("BENCH_KSTEPS", "8")
METRIC = ("decode_tok_s_"
          f"{'llama8b' if os.environ.get('BENCH_MODEL') == '8b' else 'llama1b'}"
          f"_bs{BATCH}"
          f"{'' if _KSTEPS == '8' else f'_k{_KSTEPS}'}_pallas")

PROBE_TIMEOUT_S = 120
LANE_TIMEOUT_S = 280
REPROBE_TIMEOUT_S = 60
TOTAL_BUDGET_S = 1060  # no lane launches that can't finish inside this

# Per-chip peaks for utilization reporting (bf16 FLOP/s, HBM bytes/s).
# HBM capacities live in tpu_inference/engine/autosize.py (the canonical
# table); the lane child imports it for its fits-on-chip gate.
CHIP_PEAKS = {
    "TPU v5 lite": (394e12, 819e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
}


def _r(x, nd=2):
    return round(x, nd) if x is not None else None


def _ratio(a, b, nd=3):
    return round(a / b, nd) if a is not None and b else None


# ---------------------------------------------------------------------------
# Child bodies (the only code that imports jax).
# ---------------------------------------------------------------------------

def bench_cfg(platform: str):
    import jax.numpy as jnp
    from tpu_inference.config import ModelConfig, tiny_llama

    if platform != "tpu":
        return tiny_llama()
    if os.environ.get("BENCH_MODEL") == "8b":
        # Llama-3-8B dims. bf16 weights (16 GB) don't fit one v5e chip,
        # so only the int8/int4 lanes run (bf16 lanes report skipped when
        # the bf16 model exceeds HBM); opt-in via BENCH_MODEL=8b.
        return ModelConfig(
            name="llama-8b-bench", family="llama", vocab_size=128256,
            d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            d_ff=14336, max_seq_len=2048, rope_theta=500000.0,
            dtype=jnp.bfloat16,
        )
    return ModelConfig(
        name="llama-1b-bench", family="llama", vocab_size=32000, d_model=2048,
        n_layers=22, n_heads=32, n_kv_heads=4, d_ff=5632, max_seq_len=2048,
        rope_theta=10000.0, dtype=jnp.bfloat16,
    )


def probe_child() -> None:
    import jax

    dev = jax.devices()[0]
    print(json.dumps({"platform": dev.platform,
                      "device_kind": dev.device_kind}), flush=True)


def lane_child(spec: str) -> None:
    """Measure one (backend, quant) lane; print ONE JSON record."""
    backend, quant = spec.split(":")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)

    if on_tpu:
        # Every lane needs its weights + KV pool + activations headroom
        # inside the chip's HBM, gated at 0.85 * capacity to leave room
        # for the runtime's own reservations (autosize.py tables). bf16
        # 8B exceeds one v5e; int8/int4 fit.
        from tpu_inference.engine.autosize import (detect_hbm_bytes,
                                                   weight_bytes)

        if weight_bytes(cfg, quant) >= 0.85 * detect_hbm_bytes():
            tag = "bf16" if quant == "none" else quant
            print(json.dumps({"lane": spec,
                              "skipped": f"{tag}-exceeds-hbm",
                              "model": cfg.name}), flush=True)
            return

    batch = BATCH
    prompt_len = 120
    # Fused decode steps per dispatch. BENCH_KSTEPS lets the battery A/B
    # larger fusions on the chip (fewer host round trips per token)
    # without forking the lane code; the default stays 8 so the headline
    # metric remains comparable across rounds.
    k = int(os.environ.get("BENCH_KSTEPS", "8"))
    # Hold total decoded tokens constant across K lanes (timed_calls
    # scales inversely with k): a K=16 lane that kept timed_calls=32
    # would decode twice the tokens and time its window at ~2x deeper
    # KV context, confounding the fused-K A/B with KV-bandwidth cost.
    timed_calls = max(1, (256 if on_tpu else 16) // k)
    ramp_calls = 2
    budget = (timed_calls + ramp_calls + 1) * k
    page_size = 16
    # Per-sequence page budget must cover prompt + the K-derived decode
    # budget (BENCH_KSTEPS=16 pushes prompt+budget past the old 512-token
    # cap and sequences would finish mid-measurement, silently deflating
    # the lane's tok/s).
    pages_per_seq = max(32, -(-(prompt_len + budget) // page_size))
    ecfg = EngineConfig(page_size=page_size,
                        # Pool scales with the lane's batch so BENCH_BATCH
                        # lanes never hit page-pressure mid-measurement.
                        num_pages=max(512, pages_per_seq * batch),
                        max_pages_per_seq=pages_per_seq,
                        max_batch_size=batch, prefill_buckets=(128,),
                        decode_steps_per_call=k, max_new_tokens=budget,
                        attn_backend=backend, quant=quant)
    engine = InferenceEngine(cfg, ecfg)
    t = engine.warmup()
    print(f"[bench] {spec}: warmup (XLA compile) {t:.1f}s", file=sys.stderr)

    rng = np.random.default_rng(0)
    for i in range(batch):
        seq = Sequence(request_id=i,
                       prompt_tokens=rng.integers(
                           1, cfg.vocab_size, prompt_len).tolist(),
                       max_new_tokens=budget)
        engine.prefill(seq)

    # Timed steady-state decode, both serving modes:
    # sync = one host round trip per K-step call (streaming loop);
    # chained = dispatch-ahead, device-chained carry tokens, one sync.
    for _ in range(ramp_calls):              # un-timed ramp
        engine.decode_steps()
    jax.block_until_ready(engine.kv.k)
    t0 = time.perf_counter()
    produced = 0
    for _ in range(timed_calls // 2):
        produced += sum(len(t) for t in engine.decode_steps().values())
    jax.block_until_ready(engine.kv.k)
    sync_tok_s = produced / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    out = engine.decode_steps_chained(timed_calls // 2)
    produced_c = sum(len(t) for t in out.values())
    chained_tok_s = produced_c / (time.perf_counter() - t0)

    mean_ctx = float(np.mean([s.ctx_len for s in engine.slots
                              if s is not None]))
    head = [int(t) for t in engine.slots[0].generated[:8]]
    weight_bytes = int(engine.weight_bytes)  # same math as /api/ps
    # Step-phase accounting for the lane (telemetry.py): dispatch wall
    # vs host bubble percentiles, so the roofline question ("where do
    # the missing tok/s go — compute or host?") is answered by the
    # bench artifact itself.
    phases = {k: {kk: v[kk] for kk in ("count", "sum", "p50", "p95", "p99")}
              for k, v in engine.telemetry.phase_snapshot().items()
              if k in ("decode_dispatch_s", "decode_sync_s",
                       "dispatch_bubble_s", "prefill_dispatch_s",
                       "tokens_per_dispatch")}
    # Roofline attribution for the lane (README "Performance
    # attribution"): the same verdict block the serving fleet exposes
    # at /debug/steps, computed from this lane's own step ledger.
    steps = engine.telemetry.steps_report()
    attribution = ({"enabled": False} if not steps.get("enabled") else {
        "enabled": True,
        "records": steps.get("records_window"),
        "verdicts": {kk: v.get("verdict")
                     for kk, v in (steps.get("kinds") or {}).items()},
        "rung_occupancy": steps.get("rung_occupancy") or {},
        "top_sinks": steps.get("top_sinks") or [],
        "compile_events": steps.get("compile_events"),
        "mfu": steps.get("mfu") or {},
    })
    print(json.dumps({
        "lane": spec, "model": cfg.name, "platform": platform,
        "sync_tok_s": sync_tok_s, "chained_tok_s": chained_tok_s,
        "n_params": int(engine.n_params), "weight_bytes": weight_bytes,
        "mean_ctx": mean_ctx, "head": head,
        "kv_bytes_per_token": 2 * 2 * cfg.n_layers * cfg.n_kv_heads
                              * cfg.head_dim,
        "phases": phases,
        "step_attribution": attribution,
    }), flush=True)
    del engine
    gc.collect()


def admission_lane_child() -> None:
    """reserve-vs-optimistic admission comparison through the REAL
    continuous-batching scheduler: the same burst of requests whose
    clients declare a generous token budget (num_predict) but whose
    generations stop far short of it — the BurstGPT shape that strands
    worst-case reservations. Reports occupancy / tok/s / preemption
    counters per mode; prints ONE JSON record."""
    import threading

    import jax
    import numpy as np

    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence
    from tpu_inference.engine.scheduler import EngineScheduler

    platform = jax.devices()[0].platform
    cfg = bench_cfg(platform)
    page_size = 16
    prompt_len = 64
    cap = 192                      # client-declared budget per request
    true_lens = [16, 24, 32, 48]   # actual generation lengths (cycled)
    n_requests = 24
    batch = 8
    pages_cap = -(-(prompt_len + cap) // page_size)
    # Pool holds ~3 worst-case reservations: reserve admission caps the
    # batch there; optimistic packs toward all 8 slots and preempts.
    pool = pages_cap * 3 + 1
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    out = {"lane": "admission", "model": cfg.name, "platform": platform,
           "cap_tokens": cap, "true_lens": true_lens, "pool_pages": pool}
    for mode in ("reserve", "optimistic"):
        ecfg = EngineConfig(page_size=page_size, num_pages=pool,
                            max_pages_per_seq=pages_cap + 1,
                            max_batch_size=batch, prefill_buckets=(128,),
                            decode_steps_per_call=8, admission=mode)
        engine = InferenceEngine(cfg, ecfg)
        engine.warmup()
        sched = EngineScheduler(engine).start()
        done, events = [], []
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            ev = threading.Event()
            events.append(ev)
            true_len = true_lens[i % len(true_lens)]

            def on_token(s, t, true_len=true_len):
                # Cancel at the trace's ACTUAL length — the EOS a
                # random-weight model can't emit — so the declared cap
                # stays a stranded reservation, as in real traffic.
                if len(s.generated) >= true_len:
                    sched.cancel(s.request_id)

            sched.submit(Sequence(request_id=i, prompt_tokens=list(p),
                                  max_new_tokens=cap),
                         on_token,
                         lambda s, ev=ev: (done.append(s), ev.set()))
        for ev in events:
            if not ev.wait(240):
                raise TimeoutError(f"admission lane deadlocked ({mode})")
        wall = time.perf_counter() - t0
        sched.stop(drain=True, timeout=10)
        toks = sum(len(s.generated) for s in done)
        snap = sched.stats.snapshot(engine)
        out[mode] = {
            "wall_s": _r(wall, 3),
            "tok_s": _r(toks / wall),
            "mean_batch_occupancy": _r(snap["mean_batch_occupancy"], 3),
            "peak_pages_in_use": snap["peak_pages_in_use"],
            "preemptions": engine.preemptions_total,
            "recompute_resumes": engine.resumes_total,
            "requests_rejected": snap["requests_rejected"],
        }
        del engine, sched
        gc.collect()
    out["occupancy_gain"] = _r(
        out["optimistic"]["mean_batch_occupancy"]
        - out["reserve"]["mean_batch_occupancy"], 3)
    out["tok_s_gain"] = _ratio(out["optimistic"]["tok_s"],
                               out["reserve"]["tok_s"])
    print(json.dumps(out), flush=True)


def hybrid_lane_child() -> None:
    """serial-vs-hybrid stepping comparison through the REAL
    continuous-batching scheduler: short requests decode while one long
    prompt chunk-prefills. The serial path stalls every decode lane a
    full chunk wall per chunk; hybrid steps fuse each chunk into the
    decode dispatch. Reports the decode-stall-during-prefill histogram,
    fused-step count, and the shorts' worst inter-token gap while the
    long prompt was prefilling, per mode; prints ONE JSON record."""
    import threading

    import jax
    import numpy as np

    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence
    from tpu_inference.engine.scheduler import EngineScheduler

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)
    page_size = 16
    chunk = 128 if on_tpu else 32
    long_len = 1024 if on_tpu else 200       # 8 / ~7 chunks
    short_len = 32
    short_budget = 512 if on_tpu else 256    # outlasts the prefill
    n_shorts = 6
    pages_per_seq = -(-(long_len + 8) // page_size) + 1
    rng = np.random.default_rng(0)
    out = {"lane": "hybrid", "model": cfg.name, "platform": platform,
           "chunk_tokens": chunk, "long_prompt_tokens": long_len,
           "n_decode_lanes": n_shorts}
    for mode in ("serial", "hybrid"):
        ecfg = EngineConfig(page_size=page_size,
                            num_pages=pages_per_seq * (n_shorts + 2),
                            max_pages_per_seq=pages_per_seq,
                            max_batch_size=n_shorts + 2,
                            prefill_buckets=(chunk, 2 * chunk),
                            chunked_prefill_size=chunk,
                            decode_steps_per_call=8,
                            hybrid_prefill=(mode == "hybrid"))
        engine = InferenceEngine(cfg, ecfg)
        engine.warmup()
        sched = EngineScheduler(engine).start()
        token_times = {i: [] for i in range(n_shorts)}
        done_events = []

        def on_token(s, t):
            if s.request_id < n_shorts:
                token_times[s.request_id].append(time.perf_counter())

        for i in range(n_shorts):
            ev = threading.Event()
            done_events.append(ev)
            sched.submit(
                Sequence(request_id=i,
                         prompt_tokens=rng.integers(
                             1, cfg.vocab_size, short_len).tolist(),
                         max_new_tokens=short_budget),
                on_token, lambda s, ev=ev: ev.set())
        # Let every short produce tokens before the long prompt lands, so
        # its whole chunked prefill runs against a decoding batch.
        deadline = time.perf_counter() + 120
        while (any(not t for t in token_times.values())
               and time.perf_counter() < deadline):
            time.sleep(0.005)
        long_done = threading.Event()
        long_seq = Sequence(request_id=99,
                            prompt_tokens=rng.integers(
                                1, cfg.vocab_size, long_len).tolist(),
                            max_new_tokens=4)
        t_submit = time.perf_counter()
        sched.submit(long_seq, on_token, lambda s: long_done.set())
        if not long_done.wait(240):
            raise TimeoutError(f"hybrid lane deadlocked ({mode})")
        ttft_long = (long_seq.first_token_time or time.perf_counter()) \
            - t_submit
        for i in range(n_shorts):
            sched.cancel(i)
        for ev in done_events:
            ev.wait(60)
        sched.stop(drain=True, timeout=10)
        # Worst inter-token gap any short lane saw while the long prompt
        # was prefilling (the user-visible stall the fusion removes).
        first_tok = long_seq.first_token_time or time.perf_counter()
        gaps = []
        for times in token_times.values():
            win = [t for t in times if t_submit - 1.0 <= t <= first_tok + 1.0]
            gaps += [b - a for a, b in zip(win, win[1:])]
        stall = (engine.telemetry.phase_snapshot()
                 .get("decode_stall_during_prefill_s") or {})
        out[mode] = {
            "decode_stall_count": stall.get("count", 0),
            "decode_stall_p95_s": stall.get("p95") or 0.0,
            "decode_stall_sum_s": _r(stall.get("sum") or 0.0, 4),
            "hybrid_steps": engine.hybrid_steps_total,
            "long_ttft_s": _r(ttft_long, 4),
            "short_max_gap_s": _r(max(gaps), 4) if gaps else None,
            "short_tokens_during_run": sum(len(t) for t in
                                           token_times.values()),
        }
        del engine, sched
        gc.collect()
    # Only claim the win when the serial arm actually measured a stall
    # (timing could let its chunks run against an idle batch).
    out["stall_removed"] = bool(
        out["serial"]["decode_stall_count"] > 0
        and out["hybrid"]["decode_stall_count"]
        < out["serial"]["decode_stall_count"])
    print(json.dumps(out), flush=True)


def routing_lane_child() -> None:
    """least-loaded vs prefix-affinity routing comparison through the
    REAL dp=2 EngineGroup: distinct multi-turn conversations whose every
    turn resends the full history (the BASELINE config-3 shape). Under
    least-loaded a returning turn lands on a cold replica ~half the
    time and re-prefills its whole history; prefix affinity routes it
    back to the replica holding its pages. Reports per-mode cached
    prompt tokens, returning-turn TTFT percentiles, tok/s, router
    warm/cold counts, and a greedy byte-identity check across modes;
    prints ONE JSON record."""
    import threading

    import jax
    import numpy as np

    from tpu_inference.config import EngineConfig, ServerConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence
    from tpu_inference.server.replicas import EngineGroup

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)

    def pctl(xs):
        if not xs:
            return {"p50": None, "p95": None}
        return {"p50": _r(float(np.percentile(xs, 50)), 4),
                "p95": _r(float(np.percentile(xs, 95)), 4)}

    page_size = 16
    n_convs = 6
    turns = 4
    user_tokens = 48 if on_tpu else 24   # new user message per turn
    reply_tokens = 32 if on_tpu else 12  # assistant budget per turn
    max_ctx = turns * (user_tokens + reply_tokens) + page_size
    pages_per_seq = -(-max_ctx // page_size) + 1
    buckets = (128, 256, 512) if on_tpu else (32, 64, 128, 256)
    out = {"lane": "routing", "model": cfg.name, "platform": platform,
           "dp": 2, "conversations": n_convs, "turns": turns,
           "user_tokens": user_tokens, "reply_tokens": reply_tokens}
    transcripts = {}
    for mode in ("least_loaded", "prefix_affinity"):
        ecfg = EngineConfig(page_size=page_size,
                            # Affinity can herd every conversation onto
                            # one replica: each pool holds them all.
                            num_pages=pages_per_seq * n_convs + 32,
                            max_pages_per_seq=pages_per_seq,
                            max_batch_size=n_convs,
                            prefill_buckets=buckets,
                            decode_steps_per_call=8)
        engines = [InferenceEngine(cfg, ecfg, seed=0) for _ in range(2)]
        for e in engines:
            e.warmup()
        group = EngineGroup(engines, ServerConfig(routing=mode)).start()
        # Same seed per mode: identical conversations, so the greedy
        # transcripts must match byte-for-byte across routing modes.
        rng = np.random.default_rng(0)
        histories = [rng.integers(1, cfg.vocab_size,
                                  user_tokens).tolist()
                     for _ in range(n_convs)]
        convs = {c: [] for c in range(n_convs)}
        ttft_first, ttft_return = [], []
        rid = 0
        t0 = time.perf_counter()
        total_tokens = 0
        for t in range(turns):
            seqs, events = [], []
            for c in range(n_convs):
                seq = Sequence(request_id=rid, prompt_tokens=list(
                    histories[c]), max_new_tokens=reply_tokens)
                rid += 1
                ev = threading.Event()
                group.submit(seq, lambda s, tok: None,
                             lambda s, ev=ev: ev.set())
                seqs.append(seq)
                events.append(ev)
            for ev in events:
                if not ev.wait(240):
                    raise TimeoutError(f"routing lane deadlocked ({mode})")
            for c, seq in enumerate(seqs):
                reply = list(seq.generated)
                convs[c].append(reply)
                total_tokens += len(reply)
                ttft = seq.first_token_time - seq.enqueue_time
                (ttft_return if t else ttft_first).append(ttft)
                # Next turn: full history + the reply + a new (distinct
                # per conversation) user block.
                histories[c] = (histories[c] + reply + rng.integers(
                    1, cfg.vocab_size, user_tokens).tolist())
        wall = time.perf_counter() - t0
        group.stop(drain=True, timeout=10)
        transcripts[mode] = convs
        cached_tokens = sum(s.stats.tokens_prefix_cached
                            for s in group.schedulers)
        out[mode] = {
            "wall_s": _r(wall, 3),
            "tok_s": _r(total_tokens / wall),
            "tokens_prefix_cached": cached_tokens,
            "cached_prompt_pages": cached_tokens // page_size,
            "route_warm_dispatches": group.route_prefix_hits,
            "route_cold_dispatches": group.route_cold,
            "route_hit_pages": sum(st["hit_pages"]
                                   for st in group._route_stats),
            "ttft_first_turn_s": pctl(ttft_first),
            "ttft_returning_s": pctl(ttft_return),
        }
        del group, engines
        gc.collect()
    ll, aff = out["least_loaded"], out["prefix_affinity"]
    out["outputs_identical"] = (
        transcripts["least_loaded"] == transcripts["prefix_affinity"])
    out["cached_pages_gain"] = (aff["cached_prompt_pages"]
                                - ll["cached_prompt_pages"])
    out["returning_ttft_p95_ratio"] = _ratio(
        aff["ttft_returning_s"]["p95"], ll["ttft_returning_s"]["p95"])
    out["affinity_wins"] = bool(
        aff["cached_prompt_pages"] > ll["cached_prompt_pages"]
        and aff["route_hit_pages"] > ll["route_hit_pages"]
        and out["outputs_identical"])
    print(json.dumps(out), flush=True)


def ladder_lane_child() -> None:
    """Fixed-bs8 vs batch-ladder comparison through the REAL
    continuous-batching scheduler: the same bursty mix of greedy
    requests served (a) by the single bs=8 decode graph, (b) by the
    compiled ladder up to bs=32 (engine moves between rungs as
    occupancy changes), and (c) by the ladder with host-staging reuse
    disabled (the per-dispatch bubble comparison). Reports aggregate
    tok/s, per-stream decode latency, rung telemetry, transcript
    equality, and the dispatch-bubble p95 per arm; prints ONE JSON
    record."""
    import threading

    import jax
    import numpy as np

    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.autosize import decode_ladder_rungs
    from tpu_inference.engine.engine import InferenceEngine, Sequence
    from tpu_inference.engine.scheduler import EngineScheduler

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)
    page_size = 16
    prompt_len = 48 if on_tpu else 16
    # Long enough generations that lanes persist while admission fills
    # toward the top rung (short bursts finish before the ladder climbs).
    gen_len = 96 if on_tpu else 48
    n_requests = 96 if on_tpu else 64
    top = 32
    # K=1 keeps the per-dispatch host round trip — the thing wide
    # batches amortize — in the measurement; the fused-K scan is
    # compute-bound on CPU and would understate the concurrency win.
    k_steps = 8 if on_tpu else 1
    pages_per_seq = -(-(prompt_len + gen_len) // page_size) + 1
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    out = {"lane": "ladder", "model": cfg.name, "platform": platform,
           "requests": n_requests, "prompt_tokens": prompt_len,
           "gen_tokens": gen_len, "top_rung": top, "k_steps": k_steps}
    transcripts = {}
    arms = (("bs8", 8, (), True),
            ("ladder", top, decode_ladder_rungs(top), True),
            ("ladder_rebuild", top, decode_ladder_rungs(top), False))
    for label, batch, rungs, reuse in arms:
        ecfg = EngineConfig(page_size=page_size,
                            num_pages=pages_per_seq * n_requests + 32,
                            max_pages_per_seq=pages_per_seq,
                            max_batch_size=batch, decode_ladder=rungs,
                            stage_host_reuse=reuse,
                            prefill_buckets=(64,),
                            decode_steps_per_call=k_steps)
        engine = InferenceEngine(cfg, ecfg)
        engine.warmup()
        sched = EngineScheduler(engine).start()
        done, events = [], []
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            ev = threading.Event()
            events.append(ev)
            sched.submit(Sequence(request_id=i, prompt_tokens=list(p),
                                  max_new_tokens=gen_len),
                         lambda s, t: None,
                         lambda s, ev=ev: (done.append(s), ev.set()))
        for ev in events:
            if not ev.wait(240):
                raise TimeoutError(f"ladder lane deadlocked ({label})")
        wall = time.perf_counter() - t0
        sched.stop(drain=True, timeout=10)
        toks = sum(len(s.generated) for s in done)
        tpots = [(s.finish_time - s.first_token_time)
                 / (len(s.generated) - 1)
                 for s in done if len(s.generated) > 1]
        snap = sched.stats.snapshot(engine)
        bubble = (snap.get("phases") or {}).get("dispatch_bubble_s") or {}
        transcripts[label] = {s.request_id: list(s.generated)
                              for s in done}
        out[label] = {
            "wall_s": _r(wall, 3),
            "tok_s": _r(toks / wall),
            "tpot_p50_s": _r(float(np.percentile(tpots, 50)), 5)
            if tpots else None,
            "mean_batch_occupancy": _r(snap["mean_batch_occupancy"], 3),
            "rung_peak": snap["rung_peak"],
            "rung_switches": snap["rung_switches"],
            "mfu_estimate": snap["mfu_estimate"],
            "dispatch_bubble_p95_s": bubble.get("p95"),
        }
        del engine, sched
        gc.collect()
    bs8, lad, reb = out["bs8"], out["ladder"], out["ladder_rebuild"]
    out["outputs_identical"] = (
        transcripts["bs8"] == transcripts["ladder"]
        == transcripts["ladder_rebuild"])
    out["tok_s_ratio"] = _ratio(lad["tok_s"], bs8["tok_s"])
    out["per_stream_latency_ratio"] = _ratio(lad["tpot_p50_s"],
                                             bs8["tpot_p50_s"])
    out["bubble_p95_reuse_s"] = lad["dispatch_bubble_p95_s"]
    out["bubble_p95_rebuild_s"] = reb["dispatch_bubble_p95_s"]
    # Deterministic staging micro-measure at the top rung (the bubble
    # histograms also carry scheduler/callback work; this isolates the
    # satellite's claim — per-dispatch host staging cost, reuse vs
    # rebuild). THE implementation lives in benchmarks/replay.py so
    # both committed artifacts measure the same thing.
    from benchmarks.replay import _staging_micro

    stage_us = _staging_micro(cfg, page_size=page_size,
                              num_pages=pages_per_seq * top + 32,
                              max_pages_per_seq=pages_per_seq, top=top)
    gc.collect()
    out["stage_us_per_dispatch"] = stage_us
    out["stage_reuse_speedup"] = stage_us["speedup"]
    out["ladder_wins"] = bool(
        out["outputs_identical"]
        and lad["rung_peak"] == 32
        and lad["tok_s"] > bs8["tok_s"])
    print(json.dumps(out), flush=True)


def spec_lane_child() -> None:
    """Plain decode vs draft-free ngram speculation through the REAL
    continuous-batching scheduler, two mixes per arm: an echo-heavy
    greedy multi-turn mix (turn 2 resends turn 1's transcript; the
    self-drafting win) and an adversarial no-echo sampled mix (the
    adaptive-γ throttle must keep spec within noise of plain). Reports
    pooled per-stream decode rate, aggregate tok/s, acceptance/throttle
    counters, and a greedy byte-identity check on the echo mix; prints
    ONE JSON record."""
    import threading

    import jax
    import numpy as np

    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence
    from tpu_inference.engine.scheduler import EngineScheduler

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)
    page_size = 16
    n_streams = 4
    turn_tokens = 192 if on_tpu else 160
    turn2_tokens = 128 if on_tpu else 96
    adv_tokens = 192 if on_tpu else 160
    gamma = 5
    pages_per_seq = -(-(24 + turn_tokens + turn2_tokens + 8) // page_size) + 1
    # K=1 keeps the per-dispatch round trip — what accepted speculative
    # tokens amortize — in the measurement (the ladder lane's stance).
    k_steps = 8 if on_tpu else 1
    out = {"lane": "spec", "model": cfg.name, "platform": platform,
           "streams": n_streams, "gamma": gamma,
           "turn_tokens": [turn_tokens, turn2_tokens],
           "adversarial_tokens": adv_tokens, "k_steps": k_steps}
    transcripts = {}

    def run_mix(engine, prompts, max_tokens, temperature):
        sched = EngineScheduler(engine).start()
        seqs = [Sequence(request_id=i, prompt_tokens=list(p),
                         max_new_tokens=max_tokens,
                         temperature=temperature)
                for i, p in enumerate(prompts)]
        done = {s.request_id: threading.Event() for s in seqs}
        t0 = time.perf_counter()
        for s in seqs:
            sched.submit(s, lambda sq, t: None,
                         lambda sq, d=done: d[sq.request_id].set())
        for s in seqs:
            if not done[s.request_id].wait(240):
                raise TimeoutError("spec lane deadlocked")
        wall = time.perf_counter() - t0
        sched.stop(drain=True, timeout=10)
        toks = sum(len(s.generated) for s in seqs)
        dec_t = sum(max(len(s.generated) - 1, 0) for s in seqs)
        dec_s = sum(s.finish_time - s.first_token_time for s in seqs
                    if len(s.generated) > 1)
        return seqs, {"tok_s": _r(toks / wall),
                      "per_stream_tok_s": _r(dec_t / dec_s, 1)
                      if dec_s else None}

    rng = np.random.default_rng(3)
    seed_prompts = [rng.integers(1, cfg.vocab_size, 24).tolist()
                    for _ in range(n_streams)]
    adv_prompts = [rng.integers(1, cfg.vocab_size, 24).tolist()
                   for _ in range(n_streams)]
    for label, ngram in (("plain", False), ("ngram", True)):
        ecfg = EngineConfig(
            page_size=page_size,
            num_pages=pages_per_seq * n_streams + 32,
            max_pages_per_seq=pages_per_seq, max_batch_size=n_streams,
            prefill_buckets=(64, 128, 256), decode_steps_per_call=k_steps,
            **({"spec_mode": "ngram", "num_speculative_tokens": gamma}
               if ngram else {}))
        engine = InferenceEngine(cfg, ecfg, seed=0)
        engine.warmup()
        # Echo mix: two greedy turns, turn 2 resends turn 1's transcript.
        t1, echo1 = run_mix(engine, seed_prompts, turn_tokens, 0.0)
        turn2 = [list(p) + list(s.generated)
                 for p, s in zip(seed_prompts, t1)]
        t2, echo2 = run_mix(engine, turn2, turn2_tokens, 0.0)
        transcripts[label] = ([list(s.generated) for s in t1]
                              + [list(s.generated) for s in t2])
        dec = [echo1, echo2]
        dec_rates = [d["per_stream_tok_s"] for d in dec
                     if d["per_stream_tok_s"]]
        # Adversarial mix on a FRESH engine (prefix cache/state clean).
        engine2 = InferenceEngine(cfg, ecfg, seed=0)
        engine2.warmup()
        _, adv = run_mix(engine2, adv_prompts, adv_tokens, 1.0)
        out[label] = {
            "echo_per_stream_tok_s": _r(sum(dec_rates) / len(dec_rates), 1)
            if dec_rates else None,
            "echo_tok_s": echo1["tok_s"],
            "adversarial_per_stream_tok_s": adv["per_stream_tok_s"],
            "spec_drafted": engine.spec_drafted,
            "spec_accepted": engine.spec_accepted,
            "acceptance_rate": _r(engine.spec_accepted
                                  / max(engine.spec_drafted, 1), 4),
            "adversarial_throttles": engine2.spec_throttles_total,
            "adversarial_fallback_rounds": engine2.spec_fallback_rounds,
        }
        del engine, engine2
        gc.collect()
    pl, ng = out["plain"], out["ngram"]
    out["outputs_identical"] = transcripts["plain"] == transcripts["ngram"]
    out["echo_per_stream_ratio"] = _ratio(ng["echo_per_stream_tok_s"],
                                          pl["echo_per_stream_tok_s"])
    out["adversarial_ratio"] = _ratio(ng["adversarial_per_stream_tok_s"],
                                      pl["adversarial_per_stream_tok_s"])
    out["spec_wins"] = bool(
        out["outputs_identical"] and ng["spec_accepted"] > 0
        and (out["echo_per_stream_ratio"] or 0) > 1.0)
    out["spec_never_loses"] = bool((out["adversarial_ratio"] or 0) >= 0.95)
    print(json.dumps(out), flush=True)


def tiering_lane_child() -> None:
    """Host tier off vs on through a REAL scheduler with the HBM pool
    sized ~4x below the conversations' KV working set (README "Tiered
    KV cache"): without the tier, every eviction destroys KV and a
    returning turn re-prefills its whole history; with it, evicted
    pages demote to host RAM and swap back in. Reports per-mode cached
    prompt tokens, returning-turn TTFT percentiles, tok/s, swap
    counters, and a greedy byte-identity check across modes; prints ONE
    JSON record."""
    import threading

    import jax
    import numpy as np

    from tpu_inference.config import EngineConfig
    from tpu_inference.engine.engine import InferenceEngine, Sequence
    from tpu_inference.engine.scheduler import EngineScheduler

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    cfg = bench_cfg(platform)

    def pctl(xs):
        if not xs:
            return {"p50": None, "p95": None}
        return {"p50": _r(float(np.percentile(xs, 50)), 4),
                "p95": _r(float(np.percentile(xs, 95)), 4)}

    page_size = 16
    n_convs = 6
    turns = 4
    user_tokens = 48 if on_tpu else 24
    reply_tokens = 32 if on_tpu else 12
    per_conv = turns * (user_tokens + reply_tokens)
    pages_per_seq = -(-per_conv // page_size) + 1
    ws_pages = n_convs * pages_per_seq
    num_pages = max(pages_per_seq + 6, ws_pages // 4)
    buckets = (128, 256, 512) if on_tpu else (32, 64, 128, 256)
    out = {"lane": "tiering", "model": cfg.name, "platform": platform,
           "conversations": n_convs, "turns": turns,
           "hbm_pool_pages": num_pages - 1, "working_set_pages": ws_pages,
           "working_set_over_pool": _r(ws_pages / (num_pages - 1), 2)}
    transcripts = {}
    for mode, host_pages in (("hbm_only", 0), ("tiered", 2 * ws_pages)):
        ecfg = EngineConfig(page_size=page_size, num_pages=num_pages,
                            max_pages_per_seq=pages_per_seq,
                            max_batch_size=4, prefill_buckets=buckets,
                            decode_steps_per_call=8,
                            host_cache_pages=host_pages)
        engine = InferenceEngine(cfg, ecfg, seed=0)
        engine.warmup()
        sched = EngineScheduler(engine).start()
        rng = np.random.default_rng(0)
        histories = [rng.integers(1, cfg.vocab_size, user_tokens).tolist()
                     for _ in range(n_convs)]
        convs = {c: [] for c in range(n_convs)}
        ttft_first, ttft_return = [], []
        rid = 0
        t0 = time.perf_counter()
        total_tokens = 0
        for t in range(turns):
            seqs, events = [], []
            for c in range(n_convs):
                seq = Sequence(request_id=rid, prompt_tokens=list(
                    histories[c]), max_new_tokens=reply_tokens)
                rid += 1
                ev = threading.Event()
                sched.submit(seq, lambda s, tok: None,
                             lambda s, ev=ev: ev.set())
                seqs.append(seq)
                events.append(ev)
            for ev in events:
                if not ev.wait(240):
                    raise TimeoutError(f"tiering lane deadlocked ({mode})")
            for c, seq in enumerate(seqs):
                reply = list(seq.generated)
                convs[c].append(reply)
                total_tokens += len(reply)
                ttft = seq.first_token_time - seq.enqueue_time
                (ttft_return if t else ttft_first).append(ttft)
                histories[c] = (histories[c] + reply + rng.integers(
                    1, cfg.vocab_size, user_tokens).tolist())
        wall = time.perf_counter() - t0
        sched.stop(drain=True, timeout=10)
        transcripts[mode] = convs
        pc = engine.prefix_cache.stats()
        out[mode] = {
            "wall_s": _r(wall, 3),
            "tok_s": _r(total_tokens / wall),
            "tokens_prefix_cached": sched.stats.tokens_prefix_cached,
            "offloaded_pages": pc.get("offloaded_pages", 0),
            "restored_pages": pc.get("restored_pages", 0),
            "host_evictions": pc.get("host_evictions", 0),
            "swap_in_resumes": engine.swap_in_resumes,
            "ttft_first_turn_s": pctl(ttft_first),
            "ttft_returning_s": pctl(ttft_return),
        }
        del sched, engine
        gc.collect()
    off, on = out["hbm_only"], out["tiered"]
    out["outputs_identical"] = (
        transcripts["hbm_only"] == transcripts["tiered"])
    out["cached_tokens_gain"] = (on["tokens_prefix_cached"]
                                 - off["tokens_prefix_cached"])
    out["returning_ttft_p95_ratio"] = _ratio(
        on["ttft_returning_s"]["p95"], off["ttft_returning_s"]["p95"])
    out["tiering_wins"] = bool(
        on["tokens_prefix_cached"] > off["tokens_prefix_cached"]
        and on["restored_pages"] > 0
        and out["outputs_identical"])
    print(json.dumps(out), flush=True)


# ---------------------------------------------------------------------------
# Parent orchestrator (never imports jax — cannot hang on the tunnel).
# ---------------------------------------------------------------------------

def _run_child(args, timeout, env=None):
    """Run a child, return the last JSON object on its stdout (or None).

    The child gets its own process group and its stdout goes to a temp
    file, not a pipe: on timeout the WHOLE group is SIGKILLed (a
    timeout-killed direct child leaving an orphaned TPU-runtime helper
    alive is how the round-3 tunnel wedged), and a temp file can't block
    the parent waiting for an orphan to close the write end.
    """
    import signal
    import tempfile

    with tempfile.TemporaryFile() as out:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)] + args,
            env=env, stdout=out, stderr=sys.stderr,
            start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            print(f"[bench] child {args} timed out after {timeout}s; "
                  "killing its process group", file=sys.stderr)
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
            rc = -1
        out.seek(0)
        stdout = out.read().decode(errors="replace")
    rec = None
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                pass
    return rc, rec


def _cpu_env():
    """Bypass the axon sitecustomize (a wedged relay hangs jax device
    init); cleared PYTHONPATH skips plugin registration entirely and
    JAX_PLATFORMS=cpu gives a clean CPU fallback."""
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _last_tpu_record():
    """Newest committed on-chip record for this metric, if any.

    Degraded (CPU-fallback) runs embed it so a tunnel wedge at
    measurement time cannot erase chip evidence already collected and
    committed earlier in the round (benchmarks/results/bench_r*_tpu.jsonl
    are written by benchmarks/run_tpu_round*.sh batteries). The embedded
    record is clearly separated from the live run: the live record keeps
    ``chip: cpu`` + ``degraded``; this is reported under its own key with
    the artifact path so a reader can verify provenance.
    """
    import glob
    import re
    results = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks", "results")

    def round_no(path):
        m = re.search(r"bench_r(\d+)_tpu", os.path.basename(path))
        return int(m.group(1)) if m else -1

    # Highest round first — mtime is checkout order on a fresh clone,
    # not measurement order.
    cands = sorted(glob.glob(os.path.join(results, "bench_r*_tpu.jsonl")),
                   key=round_no, reverse=True)
    for path in cands:
        best = None
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if (rec.get("platform") == "tpu"
                            and rec.get("metric") == METRIC
                            and rec.get("value")):
                        best = rec  # later snapshots supersede earlier
        except OSError:
            continue
        if best:
            rel = os.path.relpath(path, os.path.dirname(
                os.path.abspath(__file__)))
            return rel, best
    return None, None


def _snapshot(probe, lanes, degraded, partial, t_start):
    """Assemble the full record from whatever lanes have completed."""
    def lane(spec):
        rec = lanes.get(spec)
        return rec if rec and "sync_tok_s" in rec else None

    pallas, int8, dense = lane("pallas:none"), lane("pallas:int8"), \
        lane("dense:none")
    int4 = lane("pallas:int4")
    any_lane = pallas or int8 or int4 or dense

    pallas_tok_s = pallas and pallas["sync_tok_s"]
    pallas_chained = pallas and pallas["chained_tok_s"]
    int8_tok_s = int8 and int8["sync_tok_s"]
    int8_chained = int8 and int8["chained_tok_s"]
    int4_tok_s = int4 and int4["sync_tok_s"]
    int4_chained = int4 and int4["chained_tok_s"]
    dense_tok_s = dense and dense["sync_tok_s"]
    dense_chained = dense and dense["chained_tok_s"]

    # The headline is the production serving path (Pallas lanes); the
    # dense lane is comparison-only and never sets ``value`` unless no
    # Pallas lane produced a number at all.
    best_bf16 = max(pallas_tok_s or 0.0, pallas_chained or 0.0)
    best_int8 = max(int8_tok_s or 0.0, int8_chained or 0.0)
    best_int4 = max(int4_tok_s or 0.0, int4_chained or 0.0)
    best = (max(best_bf16, best_int8, best_int4)
            or max(dense_tok_s or 0.0, dense_chained or 0.0) or None)

    # mfu / hbm_util from the winning lane's resident weight bytes.
    mfu = hbm_util = mfu_bf16 = hbm_util_bf16 = None
    quant_tag = None
    if any_lane and best:
        if best_int4 and best_int4 >= max(best_bf16, best_int8) and int4:
            win = int4
        elif best_int8 >= best_bf16 and int8:
            win = int8
        else:
            win = pallas or dense
        # "dense" marks the no-Pallas-lane fallback so BENCH_r{N}.json
        # never attributes a dense-gather number to the Pallas kernel.
        quant_tag = ("int4" if win is int4 else
                     "int8" if win is int8 else
                     "bf16" if win is pallas else "dense")
        n_params = win["n_params"]
        kv_bpt = win["kv_bytes_per_token"]
        peak_flops, peak_bw = CHIP_PEAKS.get(
            probe.get("device_kind"), (394e12, 819e9))

        def util(tok_s, wbytes):
            if not tok_s:
                return None, None
            steps_per_s = tok_s / BATCH
            bw = steps_per_s * (wbytes + BATCH * kv_bpt * win["mean_ctx"])
            return (round(tok_s * 2 * n_params / peak_flops, 4),
                    round(bw / peak_bw, 4))

        mfu, hbm_util = util(best, win["weight_bytes"])
        if pallas:
            mfu_bf16, hbm_util_bf16 = util(best_bf16,
                                           pallas["weight_bytes"])

    # Mode label follows the lanes that actually supplied ``best``:
    # pallas lanes normally, the dense lane only in fallback.
    if best_bf16 or best_int8 or best_int4:
        chained_cands = [c for c in (pallas_chained, int8_chained,
                                     int4_chained) if c]
        sync_cands = [c for c in (pallas_tok_s, int8_tok_s,
                                  int4_tok_s) if c]
    else:
        chained_cands = [c for c in (dense_chained,) if c]
        sync_cands = [c for c in (dense_tok_s,) if c]
    mode = ("dispatch-ahead" if chained_cands and
            max(chained_cands) >= max(sync_cands or [0.0]) else "sync")

    heads_equal = None
    if pallas and dense:
        heads_equal = pallas["head"] == dense["head"]
        if not heads_equal and not partial:
            # Greedy sampling: any drift is a correctness signal, not
            # noise. Warn once (final snapshot), not per-snapshot.
            print(f"[bench] WARNING: backend token mismatch "
                  f"dense={dense['head']} pallas={pallas['head']}",
                  file=sys.stderr)

    skipped = {spec: rec.get("skipped") for spec, rec in lanes.items()
               if rec and rec.get("skipped")}
    rec = {
        # Name stays stable across rounds (BENCH_r{N}.json diffs by key);
        # the winning lane is reported in best_lane.
        "metric": METRIC,
        "best_lane": quant_tag,
        "value": _r(best),
        "unit": f"tokens/s (aggregate, batch={BATCH}, {mode})",
        # Like-for-like: per-stream rate vs the reference's single-stream 93.
        "vs_baseline": _ratio(best and best / BATCH, BASELINE_TOK_S),
        "vs_baseline_aggregate": _ratio(best, BASELINE_TOK_S),
        "per_stream_tok_s": _r(best and best / BATCH),
        "model": any_lane["model"] if any_lane else None,
        "sync_tok_s": _r(pallas_tok_s),
        "chained_tok_s": _r(pallas_chained),
        "dense_tok_s": _r(dense_tok_s),
        "dense_chained_tok_s": _r(dense_chained),
        "int8_tok_s": _r(int8_tok_s),
        "int8_chained_tok_s": _r(int8_chained),
        "int4_tok_s": _r(int4_tok_s),
        "int4_chained_tok_s": _r(int4_chained),
        # Mode-matched kernel comparisons (sync/sync and chained/chained).
        "pallas_speedup_vs_dense_sync": _ratio(pallas_tok_s, dense_tok_s),
        "pallas_speedup_vs_dense_chained": _ratio(pallas_chained,
                                                  dense_chained),
        "int8_speedup_vs_bf16": _ratio(best_int8 or None, best_bf16 or None),
        "int4_speedup_vs_bf16": _ratio(best_int4 or None, best_bf16 or None),
        "mfu": mfu,
        "hbm_util": hbm_util,
        "bf16_tok_s": _r(best_bf16 or None),
        "bf16_mfu": mfu_bf16,
        "bf16_hbm_util": hbm_util_bf16,
        "weight_bytes_bf16": pallas["weight_bytes"] if pallas else None,
        "weight_bytes_int8": int8["weight_bytes"] if int8 else None,
        "weight_bytes_int4": int4["weight_bytes"] if int4 else None,
        "mean_ctx": _r(any_lane.get("mean_ctx") if any_lane else None, 1),
        # Winning lane's step-phase histograms (dispatch wall / sync /
        # host bubble, p50/p95/p99): the instrumented answer to "weights
        # vs KV vs dispatch vs bubbles".
        "phase_breakdown": (win.get("phases") if any_lane and best
                            else None),
        # reserve-vs-optimistic admission comparison (occupancy / tok/s
        # / preemptions) when the lane ran.
        "admission_comparison": (
            lanes["admission"] if lanes.get("admission", {}).get("reserve")
            else None),
        # serial-vs-hybrid stepping comparison (decode stall during a
        # long prompt's chunked prefill) when the lane ran.
        "hybrid_comparison": (
            lanes["hybrid"] if lanes.get("hybrid", {}).get("serial")
            else None),
        # least-loaded vs prefix-affinity dp routing comparison (cached
        # pages / returning-turn TTFT) when the lane ran.
        "routing_comparison": (
            lanes["routing"] if lanes.get("routing", {}).get("least_loaded")
            else None),
        # fixed-bs8 vs compiled batch ladder comparison (aggregate tok/s
        # at the HBM-sized rung, per-stream latency, byte-identity, host
        # staging bubble) when the lane ran.
        "ladder_comparison": (
            lanes["ladder"] if lanes.get("ladder", {}).get("bs8")
            else None),
        # plain vs draft-free ngram speculation comparison (echo-mix
        # per-stream decode ratio + byte-identity, adversarial-mix
        # never-loses ratio) when the lane ran.
        "spec_comparison": (
            lanes["spec"] if lanes.get("spec", {}).get("plain")
            else None),
        "chip": probe.get("device_kind"),
        "platform": probe.get("platform"),
        "backends_token_equal": heads_equal,
        "partial": partial,
        "wall_s": _r(time.perf_counter() - t_start, 1),
    }
    if degraded:
        rec["degraded"] = degraded
        art, tpu_rec = _last_tpu_record()
        if tpu_rec:
            rec["last_tpu_artifact"] = art
            rec["last_tpu_result"] = {
                k: tpu_rec.get(k) for k in (
                    "value", "unit", "best_lane", "vs_baseline",
                    "vs_baseline_aggregate", "per_stream_tok_s",
                    "bf16_tok_s", "int8_tok_s", "int8_chained_tok_s",
                    "int4_chained_tok_s", "pallas_speedup_vs_dense_chained",
                    "int8_speedup_vs_bf16", "int4_speedup_vs_bf16",
                    "mfu", "hbm_util", "backends_token_equal", "chip")}
    if skipped:
        rec["lanes_skipped"] = skipped
    print(json.dumps(rec), flush=True)


def orchestrate() -> None:
    t_start = time.perf_counter()
    env = None
    degraded = None

    rc, probe = _run_child(["--probe"], PROBE_TIMEOUT_S)
    if probe is None:
        print("[bench] probe failed; retrying once in 15s", file=sys.stderr)
        time.sleep(15)
        rc, probe = _run_child(["--probe"], PROBE_TIMEOUT_S)
    if probe is None:
        print("[bench] TPU tunnel unreachable; falling back to CPU "
              "(sitecustomize bypass) at test scale", file=sys.stderr)
        env = _cpu_env()
        degraded = "tpu-tunnel-wedged; CPU fallback at test scale"
        rc, probe = _run_child(["--probe"], REPROBE_TIMEOUT_S, env)
    if probe is None:
        # Nothing can initialize: still emit a well-formed record.
        print(json.dumps({"metric": METRIC, "value": None,
                          "unit": "tokens/s", "vs_baseline": None,
                          "skipped": "tpu-unavailable",
                          "wall_s": _r(time.perf_counter() - t_start, 1)}),
              flush=True)
        return

    on_tpu = probe["platform"] == "tpu"
    print(f"[bench] platform={probe['platform']} "
          f"chip={probe.get('device_kind')}", file=sys.stderr)
    lane_timeout = LANE_TIMEOUT_S if on_tpu else 240
    lanes = {}
    give_up = False

    def budget_left():
        return TOTAL_BUDGET_S - (time.perf_counter() - t_start)

    # Headline lane first so even the first snapshot carries the number
    # the round is judged on.
    for spec in ("pallas:none", "pallas:int8", "pallas:int4",
                 "dense:none"):
        if give_up:
            lanes[spec] = {"lane": spec, "skipped": "tpu-wedged-midrun"}
            continue
        if budget_left() < lane_timeout:
            lanes[spec] = {"lane": spec, "skipped": "budget-exhausted"}
            continue
        rc, rec = _run_child(["--lane", spec], lane_timeout, env)
        if rec is None and on_tpu:
            # Distinguish a dead tunnel (skip the rest) from a transient
            # dial error (the lane deserves one retry).
            _, p2 = _run_child(["--probe"], REPROBE_TIMEOUT_S)
            if p2 is None:
                print("[bench] tunnel lost mid-run; skipping remaining "
                      "lanes", file=sys.stderr)
                give_up = True
            elif budget_left() >= lane_timeout:
                print(f"[bench] retrying lane {spec} (tunnel healthy)",
                      file=sys.stderr)
                rc, rec = _run_child(["--lane", spec], lane_timeout, env)
        if rec is None:
            rec = {"lane": spec, "skipped": f"lane-failed rc={rc}"}
        lanes[spec] = rec
        _snapshot(probe, lanes, degraded, partial=True, t_start=t_start)
    # Admission-mode comparison lane (reserve vs optimistic through the
    # scheduler): measurement-only extra — it never sets ``value`` and a
    # failure/skip costs nothing but its own field.
    if give_up:
        lanes["admission"] = {"lane": "admission",
                              "skipped": "tpu-wedged-midrun"}
    elif budget_left() < lane_timeout:
        lanes["admission"] = {"lane": "admission",
                              "skipped": "budget-exhausted"}
    else:
        rc, rec = _run_child(["--admission-lane"], lane_timeout, env)
        lanes["admission"] = rec or {"lane": "admission",
                                     "skipped": f"lane-failed rc={rc}"}
        _snapshot(probe, lanes, degraded, partial=True, t_start=t_start)
    # Hybrid-stepping comparison lane (serial vs fused chunked prefill
    # through the scheduler): measurement-only extra, like admission.
    if give_up:
        lanes["hybrid"] = {"lane": "hybrid", "skipped": "tpu-wedged-midrun"}
    elif budget_left() < lane_timeout:
        lanes["hybrid"] = {"lane": "hybrid", "skipped": "budget-exhausted"}
    else:
        rc, rec = _run_child(["--hybrid-lane"], lane_timeout, env)
        lanes["hybrid"] = rec or {"lane": "hybrid",
                                  "skipped": f"lane-failed rc={rc}"}
        _snapshot(probe, lanes, degraded, partial=True, t_start=t_start)
    # dp routing comparison lane (least-loaded vs prefix-affinity
    # through the real EngineGroup): measurement-only extra as well.
    if give_up:
        lanes["routing"] = {"lane": "routing",
                            "skipped": "tpu-wedged-midrun"}
    elif budget_left() < lane_timeout:
        lanes["routing"] = {"lane": "routing", "skipped": "budget-exhausted"}
    else:
        rc, rec = _run_child(["--routing-lane"], lane_timeout, env)
        lanes["routing"] = rec or {"lane": "routing",
                                   "skipped": f"lane-failed rc={rc}"}
        _snapshot(probe, lanes, degraded, partial=True, t_start=t_start)
    # Batch-ladder comparison lane (fixed bs=8 vs the compiled ladder
    # through the scheduler): measurement-only extra as well.
    if give_up:
        lanes["ladder"] = {"lane": "ladder", "skipped": "tpu-wedged-midrun"}
    elif budget_left() < lane_timeout:
        lanes["ladder"] = {"lane": "ladder", "skipped": "budget-exhausted"}
    else:
        rc, rec = _run_child(["--ladder-lane"], lane_timeout, env)
        lanes["ladder"] = rec or {"lane": "ladder",
                                  "skipped": f"lane-failed rc={rc}"}
        _snapshot(probe, lanes, degraded, partial=True, t_start=t_start)
    # Draft-free speculation comparison lane (plain vs ngram spec
    # through the scheduler, echo + adversarial mixes): measurement-only
    # extra as well.
    if give_up:
        lanes["spec"] = {"lane": "spec", "skipped": "tpu-wedged-midrun"}
    elif budget_left() < lane_timeout:
        lanes["spec"] = {"lane": "spec", "skipped": "budget-exhausted"}
    else:
        rc, rec = _run_child(["--spec-lane"], lane_timeout, env)
        lanes["spec"] = rec or {"lane": "spec",
                                "skipped": f"lane-failed rc={rc}"}
        _snapshot(probe, lanes, degraded, partial=True, t_start=t_start)
    # Tiered-KV-cache comparison lane (host tier off vs on through the
    # scheduler, pool ~4x oversubscribed): measurement-only extra too.
    if give_up:
        lanes["tiering"] = {"lane": "tiering",
                            "skipped": "tpu-wedged-midrun"}
    elif budget_left() < lane_timeout:
        lanes["tiering"] = {"lane": "tiering", "skipped": "budget-exhausted"}
    else:
        rc, rec = _run_child(["--tiering-lane"], lane_timeout, env)
        lanes["tiering"] = rec or {"lane": "tiering",
                                   "skipped": f"lane-failed rc={rc}"}
    _snapshot(probe, lanes, degraded, partial=False, t_start=t_start)


if __name__ == "__main__":
    if "--probe" in sys.argv:
        probe_child()
    elif "--admission-lane" in sys.argv:
        admission_lane_child()
    elif "--hybrid-lane" in sys.argv:
        hybrid_lane_child()
    elif "--routing-lane" in sys.argv:
        routing_lane_child()
    elif "--ladder-lane" in sys.argv:
        ladder_lane_child()
    elif "--spec-lane" in sys.argv:
        spec_lane_child()
    elif "--tiering-lane" in sys.argv:
        tiering_lane_child()
    elif "--lane" in sys.argv:
        lane_child(sys.argv[sys.argv.index("--lane") + 1])
    else:
        orchestrate()
